"""loopcheck: event-loop blocking analysis over the project call graph.

The API front door is ONE asyncio loop; a single blocked callback
freezes every tenant's stream at once. These rules find the blocking
before it ships, using :mod:`tools.jaxlint.callgraph` so one level of
helper indirection (``async handler → _encode_png → PIL``) no longer
hides it:

``blocking-in-async``
    a blocking leaf — device round-trip, ``time.sleep``, gRPC/replica
    RPC, file/PIL/subprocess I/O, lock/future wait — in an ``async
    def``'s own scope, or a call to a sync project helper that
    transitively reaches one. Offload with ``await
    loop.run_in_executor(...)`` / ``asyncio.to_thread(...)``, or
    annotate ``# jaxlint: offloaded (reason)`` when the code provably
    runs executor-side.

``blocking-in-stream``
    the same sites inside an async *generator* (or an ``async for``
    body) — SSE streams stall between every chunk, which multiplies
    the damage by the token count.

``async-lock-blocking-await``
    an ``asyncio.Lock`` held across an ``await`` of an executor
    offload or of a slow async callee. The loop keeps turning, but the
    lock is pinned for the blocked call's full wall time — every other
    task needing it queues behind one straggler.

``coroutine-not-awaited``
    a statement-position call of a project ``async def`` whose
    coroutine is discarded — the body never runs. (The runtime warning
    for this only fires at GC time, usually far from the bug.)

Test files (``test_*``/``conftest``) are skipped: tests block event
loops on purpose (fixtures simulating slow handlers). The runtime
cross-check for everything static analysis cannot see — attribute-of-
attribute receivers, dynamic dispatch — is ``tools/loopsan.py``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterator, Optional

from tools.jaxlint.callgraph import (
    OFFLOADED_RE,
    CallGraph,
    FuncNode,
    build_graph,
    is_offloader,
    own_scope,
)
from tools.jaxlint.core import Finding, Module

ASYNC_LOCK_CTORS = {
    "asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore",
    "asyncio.BoundedSemaphore",
}

OFFLOAD_HINT = ("offload it (`await loop.run_in_executor(...)` / "
                "`asyncio.to_thread(...)`) or annotate "
                "`# jaxlint: offloaded (reason)` if it provably runs "
                "executor-side")


def _is_test_file(path: str) -> bool:
    return Path(path).name.startswith(("test_", "conftest"))


def _async_lock_exprs(module: Module) -> set[str]:
    """Unparsed assignment targets bound to asyncio sync primitives —
    ``{"self._lock", "lock"}`` — matched textually against ``async
    with`` context expressions."""
    cached = module.__dict__.get("_async_lock_exprs")
    if cached is not None:
        return cached
    out: set[str] = set()
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        if module.dotted(node.value.func) not in ASYNC_LOCK_CTORS:
            continue
        for t in node.targets:
            try:
                out.add(ast.unparse(t))
            except Exception:
                pass
    module.__dict__["_async_lock_exprs"] = out
    return out


class _Analysis:
    """All four rules' findings, computed in one pass over the graph and
    cached on it — each ProjectRule below just reads its bucket."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.findings: dict[str, list[Finding]] = {
            "blocking-in-async": [],
            "blocking-in-stream": [],
            "async-lock-blocking-await": [],
            "coroutine-not-awaited": [],
        }
        self._slow_memo: dict[str, bool] = {}
        for fn in graph.functions.values():
            if _is_test_file(fn.module.path):
                continue
            if fn.is_async and not fn.offloaded:
                self._check_async_fn(fn)
                self._check_lock_spans(fn)
        for m in graph.modules:
            if not _is_test_file(m.path):
                self._check_discarded(m)

    # -- blocking-in-async / blocking-in-stream ---------------------------

    def _stream_ctx(self, fn: FuncNode, node: ast.AST) -> bool:
        """The site stalls a stream: the enclosing async def is a
        generator, or the site sits in an ``async for`` body."""
        if fn.is_generator:
            return True
        m = fn.module
        for anc in m.ancestors(node):
            if anc is fn.node:
                break
            if isinstance(anc, ast.AsyncFor):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                break
        return False

    def _emit(self, fn: FuncNode, node: ast.AST, what: str) -> None:
        if self._stream_ctx(fn, node):
            rule = "blocking-in-stream"
            consequence = (f"stalls the SSE/stream consumer between "
                           f"chunks in `async def {fn.qualname}`")
        else:
            rule = "blocking-in-async"
            consequence = (f"blocks the event loop in `async def "
                           f"{fn.qualname}` — every other request "
                           f"freezes for its duration")
        self.findings[rule].append(fn.module.finding(
            node, rule, f"{what} {consequence}; {OFFLOAD_HINT}"))

    def _check_async_fn(self, fn: FuncNode) -> None:
        m = fn.module
        for s in fn.sites:
            if "async" in s.domains:
                self._emit(fn, s.node, s.desc)
        for e in fn.edges:
            if e.awaited:
                continue
            callee = self.graph.functions.get(e.callee)
            if callee is None or callee.is_async:
                continue
            if OFFLOADED_RE.search(m.line_text(e.node.lineno)):
                continue
            chain = self.graph.taint(e.callee, "async")
            if chain is None:
                continue
            path = " → ".join([callee.qualname] + chain)
            self._emit(fn, e.node,
                       f"the inline call `{callee.qualname}(...)` is "
                       f"blocking-tainted ({path}), so it")

    # -- async-lock-blocking-await ----------------------------------------

    def _async_slow(self, key: str,
                    _stack: Optional[frozenset] = None) -> bool:
        """The async function's own wall time can be long: it has a
        blocking leaf, awaits an executor offload, calls a tainted sync
        helper, or awaits another slow async project callee."""
        if key in self._slow_memo:
            return self._slow_memo[key]
        fn = self.graph.functions.get(key)
        if fn is None or fn.offloaded:
            return False
        stack = _stack or frozenset()
        if key in stack:
            return False
        out = any("async" in s.domains for s in fn.sites)
        if not out:
            for node in own_scope(fn.node):
                if (isinstance(node, ast.Call)
                        and is_offloader(fn.module, node)):
                    out = True
                    break
        if not out:
            for e in fn.edges:
                callee = self.graph.functions.get(e.callee)
                if callee is None:
                    continue
                if callee.is_async:
                    if e.awaited and self._async_slow(
                            e.callee, stack | {key}):
                        out = True
                        break
                elif self.graph.taint(e.callee, "async") is not None:
                    out = True
                    break
        self._slow_memo[key] = out
        return out

    def _check_lock_spans(self, fn: FuncNode) -> None:
        m = fn.module
        locks = _async_lock_exprs(m)
        if not locks:
            return
        for stmt in own_scope(fn.node):
            if not isinstance(stmt, ast.AsyncWith):
                continue
            held = None
            for item in stmt.items:
                try:
                    src = ast.unparse(item.context_expr)
                except Exception:
                    continue
                if src in locks:
                    held = src
                    break
            if held is None:
                continue
            for node in self._with_scope(stmt):
                if not isinstance(node, ast.Await):
                    continue
                val = node.value
                if not isinstance(val, ast.Call):
                    continue
                what = None
                if is_offloader(m, val):
                    what = "an executor offload"
                else:
                    key = self.graph.resolve_call(m, fn.cls, val)
                    if key is not None:
                        callee = self.graph.functions[key]
                        if callee.is_async and self._async_slow(key):
                            what = (f"slow `async def "
                                    f"{callee.qualname}` (it offloads "
                                    f"or reaches blocking work)")
                if what is None:
                    continue
                if OFFLOADED_RE.search(m.line_text(node.lineno)):
                    continue
                self.findings["async-lock-blocking-await"].append(
                    m.finding(
                        node, "async-lock-blocking-await",
                        f"awaiting {what} while holding asyncio lock "
                        f"`{held}` in `async def {fn.qualname}` pins "
                        f"the lock for the call's full wall time — "
                        f"every task needing it queues behind this "
                        f"one; copy what the call needs, release the "
                        f"lock, then await",
                    ))

    @staticmethod
    def _with_scope(stmt: ast.AsyncWith) -> Iterator[ast.AST]:
        nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        stack = list(stmt.body)
        while stack:
            node = stack.pop()
            if isinstance(node, nested):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- coroutine-not-awaited --------------------------------------------

    def _check_discarded(self, m: Module) -> None:
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            cls = None
            for anc in m.ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    cls = anc.name
                    break
            key = self.graph.resolve_call(m, cls, node.value)
            if key is None:
                continue
            callee = self.graph.functions[key]
            if not callee.is_async:
                continue
            self.findings["coroutine-not-awaited"].append(m.finding(
                node, "coroutine-not-awaited",
                f"statement-position call of `async def "
                f"{callee.qualname}` discards the coroutine — the body "
                f"never runs; `await` it or hand it to "
                f"`asyncio.create_task(...)`",
            ))


def loop_analysis(modules: list[Module]) -> _Analysis:
    graph = build_graph(modules)
    analysis = getattr(graph, "_loop_analysis", None)
    if analysis is None:
        analysis = _Analysis(graph)
        graph._loop_analysis = analysis
    return analysis


class _LoopRule:
    """Base: collect the module set, share one analysis per run."""

    id = ""
    doc = ""

    def __init__(self):
        self._modules: list[Module] = []

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    def finalize(self) -> Iterator[Finding]:
        if not self._modules:
            return
        yield from loop_analysis(self._modules).findings[self.id]


class BlockingInAsync(_LoopRule):
    id = "blocking-in-async"
    doc = ("blocking call (device sync, sleep, gRPC, file/PIL/"
           "subprocess I/O, lock/future wait) reachable from an async "
           "def — directly or through sync project helpers")


class BlockingInStream(_LoopRule):
    id = "blocking-in-stream"
    doc = ("blocking call inside an async stream generator or `async "
           "for` body — stalls every consumer between chunks")


class AsyncLockBlockingAwait(_LoopRule):
    id = "async-lock-blocking-await"
    doc = ("asyncio.Lock held across an await of an executor offload "
           "or a blocking-tainted async callee")


class CoroutineNotAwaited(_LoopRule):
    id = "coroutine-not-awaited"
    doc = ("statement-position call of a project async def whose "
           "coroutine is never awaited — the body never runs")
