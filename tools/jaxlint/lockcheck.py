"""lockcheck: lock-discipline dataflow over classes that own locks.

The host plane of this repo is a many-thread system (fleet pool/router,
batch executor, obs watchdog/flight/SLO, scheduler lanes) built on
``threading.Lock``/``RLock``. Its two recurring review-fix classes are

  1. a shared attribute written under ``with self._lock`` in one method
     but read or written lock-free somewhere else (the PR 8 counter
     bugs), and
  2. a blocking operation — device round-trip, replica/worker RPC,
     ``time.sleep`` — performed while a lock is held, freezing every
     thread that needs the lock for the duration (the PR 7 scrape
     stall).

This pass models each class: attributes with at least one write under a
held lock (outside ``__init__``) are *guarded*; every other access of a
guarded attribute must hold that lock. Annotations refine the model:

  ``# jaxlint: guarded-by(_lock)`` on a ``def`` line
      the method's callers hold ``_lock`` (private helpers);
  on an attribute assignment in ``__init__``
      declares the attribute guarded even before any locked write;
  on any other statement
      asserts that statement runs with the lock held.

Deliberately lock-free reads (host-mirror snapshots, monotone-counter
scrapes) are waived in place with the standard
``# jaxlint: disable=lock-guarded-attr (reason)`` comment — the reason
is the documentation the next reader needs.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

# the blocking-call vocabulary is shared with the call-graph layer (and
# through it with loopcheck): one definition of "what blocks a thread"
from tools.jaxlint.callgraph import (  # noqa: F401 — re-exported names
    BLOCKING_DOTTED,
    BLOCKING_METHODS,
    CLIENT_RPC_METHODS,
    DEVICEISH,
    NP_GATHERS,
    RPC_METHODS,
    build_graph,
)
from tools.jaxlint.core import SUPPRESS_RE, Finding, Module

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
# attributes holding these are thread-safe sync primitives themselves —
# calling .set()/.wait()/.put() on them lock-free is their whole point
SYNC_CTORS = {
    "threading.Event", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "Event", "Condition", "Semaphore",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
}

# receiver methods that mutate the container they're called on
MUTATORS = {
    "append", "appendleft", "add", "discard", "remove", "pop", "popleft",
    "clear", "update", "extend", "insert", "setdefault", "popitem",
    "put", "put_nowait",
}

@dataclasses.dataclass
class Access:
    attr: str
    node: ast.AST
    write: bool
    held: frozenset       # lock names held at this point
    method: str


@dataclasses.dataclass
class BlockingCall:
    node: ast.AST
    what: str
    held: frozenset
    method: str


class ClassLockModel:
    """Per-class lock/attribute model built by one AST walk."""

    def __init__(self, module: Module, cls: ast.ClassDef):
        self.module = module
        self.cls = cls
        self.locks: set[str] = set()
        self.sync_attrs: set[str] = set()
        self.accesses: list[Access] = []
        self.blocking: list[BlockingCall] = []
        # non-blocking calls made WITH a lock held: resolved against the
        # project call graph at finalize time (helper indirection)
        self.candidates: list[BlockingCall] = []
        self.method_lines: dict[str, int] = {}
        # attr -> set of lock names it was written under / declared with
        self.guards: dict[str, set[str]] = {}
        self._find_locks()
        if self.locks:
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_method(fn)
            self._infer_guards()

    # -- model construction ----------------------------------------------

    def _find_locks(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = self.module.dotted(node.value.func)
            if ctor not in LOCK_CTORS and ctor not in SYNC_CTORS:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    if ctor in LOCK_CTORS:
                        self.locks.add(t.attr)
                    else:
                        self.sync_attrs.add(t.attr)

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """``self._lock`` → ``_lock`` when it names a tracked lock."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and expr.attr in self.locks):
            return expr.attr
        return None

    def _walk_method(self, fn) -> None:
        # the signature may span lines; annotations/waivers count on any
        # of them (a trailing comment naturally lands on the `:` line)
        sig_end = fn.body[0].lineno if fn.body else fn.lineno + 1
        sig_lines = range(fn.lineno, max(fn.lineno + 1, sig_end))
        self.method_lines[fn.name] = sig_lines
        held = frozenset()
        for line in sig_lines:
            held = held | self.module.guarded_by(line)
        # manual acquire()/release() of a tracked lock: treat the whole
        # method as holding it — conservative, but manual lock management
        # is rare here and the alternative is a false-positive storm
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")):
                name = self._lock_name(node.func.value)
                if name:
                    held = held | {name}
        self._walk_stmts(fn.body, held, fn.name)

    def _walk_stmts(self, stmts, held: frozenset, method: str) -> None:
        for stmt in stmts:
            h = held | self.module.guarded_by(stmt.lineno)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in stmt.items:
                    name = self._lock_name(item.context_expr)
                    if name:
                        acquired.add(name)
                    else:
                        self._record_expr(item.context_expr, h, method)
                self._walk_stmts(stmt.body, h | acquired, method)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs LATER (thread target, callback): locks
                # held at definition time are not held at run time
                self._walk_stmts(
                    stmt.body, self.module.guarded_by(stmt.lineno),
                    method)
            elif isinstance(stmt, ast.ClassDef):
                continue
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._record_expr(stmt.iter, h, method)
                self._record_expr(stmt.target, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.While):
                self._record_expr(stmt.test, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.If):
                self._record_expr(stmt.test, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.Try):
                self._walk_stmts(stmt.body, h, method)
                for hd in stmt.handlers:
                    self._walk_stmts(hd.body, h, method)
                self._walk_stmts(stmt.orelse + stmt.finalbody, h, method)
            else:
                self._record_stmt(stmt, h, method)

    def _record_stmt(self, stmt, held: frozenset, method: str) -> None:
        # classify write targets first so _record_expr can skip them
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._record_target(t, held, method)
            self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.AugAssign):
            self._record_target(stmt.target, held, method, aug=True)
            self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.AnnAssign):
            self._record_target(stmt.target, held, method)
            if stmt.value is not None:
                self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_target(t, held, method)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._record_expr(child, held, method)

    def _self_attr(self, node) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in self.locks
                and node.attr not in self.sync_attrs):
            return node.attr
        return None

    def _record_target(self, target, held, method, aug=False) -> None:
        """An assignment target: ``self.x``, ``self.x[k]``, tuples."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._record_target(e, held, method, aug=aug)
            return
        root = target
        while isinstance(root, ast.Subscript):
            self._record_expr(root.slice, held, method)
            root = root.value
        attr = self._self_attr(root)
        if attr is not None:
            self.accesses.append(
                Access(attr, target, True, held, method))
        elif isinstance(root, (ast.Attribute, ast.Name)):
            self._record_expr(root, held, method)

    def _record_expr(self, expr, held: frozenset, method: str) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._classify_call(node, held, method)
            attr = self._self_attr(node) if isinstance(
                node, ast.Attribute) else None
            if attr is None:
                continue
            parent = self.module.parents.get(node)
            write = (
                isinstance(node.ctx, (ast.Store, ast.Del))
                or (isinstance(parent, ast.Attribute)
                    and parent.attr in MUTATORS
                    and isinstance(self.module.parents.get(parent),
                                   ast.Call))
            )
            self.accesses.append(Access(attr, node, write, held, method))

    def _classify_call(self, node: ast.Call, held, method) -> None:
        if not held:
            return
        what = self._blocking_kind(node)
        if what:
            self.blocking.append(BlockingCall(node, what, held, method))
        else:
            self.candidates.append(BlockingCall(node, "", held, method))

    def _blocking_kind(self, node: ast.Call) -> Optional[str]:
        name = self.module.dotted(node.func)
        if name in BLOCKING_DOTTED:
            return f"`{name}(...)`"
        if name in NP_GATHERS and node.args:
            try:
                src = ast.unparse(node.args[0])
            except Exception:
                src = ""
            if DEVICEISH.search(src):
                return f"`{name}(...)` device gather"
        func = node.func
        if not isinstance(func, ast.Attribute):
            return None
        if func.attr == "item" and not node.args and not node.keywords:
            return "`.item()` device sync"
        if func.attr in ("block_until_ready",):
            return "`.block_until_ready()` device sync"
        if func.attr in ("result", "wait"):
            return f"`.{func.attr}(...)` blocking wait"
        if func.attr in RPC_METHODS:
            return f"gRPC `.{func.attr}(...)`"
        try:
            recv = ast.unparse(func.value)
        except Exception:
            recv = ""
        if "stub" in recv.split("."):
            return f"gRPC `{recv}.{func.attr}(...)`"
        if func.attr in CLIENT_RPC_METHODS and recv != "self":
            return f"replica/worker RPC `.{func.attr}(...)`"
        return None

    # -- guard inference ---------------------------------------------------

    def _infer_guards(self) -> None:
        # explicit declarations: `self.x = ...  # jaxlint: guarded-by(_lk)`
        for node in ast.walk(self.cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            declared = self.module.guarded_by(node.lineno)
            declared = {d for d in declared if d in self.locks}
            if not declared:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                root = t
                while isinstance(root, ast.Subscript):
                    root = root.value
                attr = self._self_attr(root)
                if attr:
                    self.guards.setdefault(attr, set()).update(declared)
        # inferred: written under a held lock outside __init__
        for a in self.accesses:
            if a.write and a.held and a.method != "__init__":
                self.guards.setdefault(a.attr, set()).update(a.held)


def method_waived(module: Module, model: ClassLockModel,
                  method: str, rule: str) -> bool:
    """A ``# jaxlint: disable=<rule>`` on a METHOD's ``def`` line waives
    the whole body — the idiom for single-owner-thread structures where
    every lock-free access in the method is the same deliberate design
    (one documented waiver instead of one per line)."""
    for line in model.method_lines.get(method, ()):
        m = SUPPRESS_RE.search(module.line_text(line))
        if not m:
            continue
        ids = {p.strip() for p in m.group(1).split(",")}
        if "all" in ids or rule in ids:
            return True
    return False


def lock_models(module: Module) -> list[ClassLockModel]:
    cached = module.__dict__.get("_lock_models")
    if cached is None:
        cached = [
            ClassLockModel(module, node)
            for node in ast.walk(module.tree)
            if isinstance(node, ast.ClassDef)
        ]
        module.__dict__["_lock_models"] = cached
    return cached


class LockGuardedAttr:
    """Reads/writes of a lock-guarded attribute without the lock.

    An attribute written under ``with self._lock`` anywhere (or declared
    with ``guarded-by``) is shared mutable state; touching it lock-free
    in another method is a data race until proven otherwise. Intentional
    lock-free reads (host mirrors, monotone counters feeding a scrape)
    get an inline ``disable`` with the reason spelled out.
    """

    id = "lock-guarded-attr"
    doc = ("read/write of an attribute guarded by a class lock "
           "(written under `with self._lock` elsewhere) while the lock "
           "is not held")

    def check(self, module: Module) -> Iterator[Finding]:
        for model in lock_models(module):
            for a in model.accesses:
                guard = model.guards.get(a.attr)
                if not guard or a.method == "__init__":
                    continue
                if a.held & guard:
                    continue
                if method_waived(module, model, a.method, self.id):
                    continue
                kind = "write to" if a.write else "read of"
                lock = "/".join(sorted(guard))
                yield module.finding(
                    a.node, self.id,
                    f"{kind} '{a.attr}' outside `self.{lock}` — it is "
                    f"written under that lock elsewhere in "
                    f"{model.cls.name}; take the lock, or waive with a "
                    f"reason if the lock-free access is intentional",
                )


class BlockingUnderLock:
    """Blocking operations while holding a class lock.

    A device round-trip, replica/worker RPC, future/event wait, or
    ``time.sleep`` under a lock blocks every thread that needs the lock
    for the call's full duration — the PR 7 scrape stall (stats RPCs
    under the manager lock) as a lint rule. Copy what the call needs,
    release the lock, then block.

    A ProjectRule since the loopcheck PR: locked calls that resolve
    through the project call graph to a blocking-tainted helper are
    flagged too, so ``with self._lock: self._refresh()`` no longer
    hides the RPC one ``def`` away inside ``_refresh``.
    """

    id = "blocking-under-lock"
    doc = ("device sync, gRPC/replica RPC, future/event wait, "
           "subprocess, or time.sleep performed while a threading lock "
           "is held — directly or via a project helper (call graph)")

    def __init__(self):
        self._modules: list[Module] = []

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    def finalize(self) -> Iterator[Finding]:
        from tools.jaxlint.callgraph import OFFLOADED_RE

        graph = build_graph(self._modules)
        for module in self._modules:
            for model in lock_models(module):
                for b in model.blocking:
                    if method_waived(module, model, b.method, self.id):
                        continue
                    lock = "/".join(sorted(b.held))
                    yield module.finding(
                        b.node, self.id,
                        f"{b.what} while holding `self.{lock}` in "
                        f"{model.cls.name}.{b.method} blocks every "
                        f"thread needing the lock; move the call "
                        f"outside the critical section",
                    )
                for c in model.candidates:
                    if method_waived(module, model, c.method, self.id):
                        continue
                    if OFFLOADED_RE.search(
                            module.line_text(c.node.lineno)):
                        continue
                    chain = graph.call_taint(
                        module, model.cls.name, c.node, domain="lock")
                    if chain is None:
                        continue
                    lock = "/".join(sorted(c.held))
                    path = " → ".join(chain)
                    yield module.finding(
                        c.node, self.id,
                        f"call to `{chain[0]}(...)` while holding "
                        f"`self.{lock}` in {model.cls.name}.{c.method} "
                        f"reaches blocking work ({path}); move the "
                        f"call outside the critical section",
                    )
