"""lockcheck: lock-discipline dataflow over classes that own locks.

The host plane of this repo is a many-thread system (fleet pool/router,
batch executor, obs watchdog/flight/SLO, scheduler lanes) built on
``threading.Lock``/``RLock``. Its two recurring review-fix classes are

  1. a shared attribute written under ``with self._lock`` in one method
     but read or written lock-free somewhere else (the PR 8 counter
     bugs), and
  2. a blocking operation — device round-trip, replica/worker RPC,
     ``time.sleep`` — performed while a lock is held, freezing every
     thread that needs the lock for the duration (the PR 7 scrape
     stall).

This pass models each class: attributes with at least one write under a
held lock (outside ``__init__``) are *guarded*; every other access of a
guarded attribute must hold that lock. Annotations refine the model:

  ``# jaxlint: guarded-by(_lock)`` on a ``def`` line
      the method's callers hold ``_lock`` (private helpers);
  on an attribute assignment in ``__init__``
      declares the attribute guarded even before any locked write;
  on any other statement
      asserts that statement runs with the lock held.

Deliberately lock-free reads (host-mirror snapshots, monotone-counter
scrapes) are waived in place with the standard
``# jaxlint: disable=lock-guarded-attr (reason)`` comment — the reason
is the documentation the next reader needs.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator, Optional

from tools.jaxlint.core import SUPPRESS_RE, Finding, Module

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
# attributes holding these are thread-safe sync primitives themselves —
# calling .set()/.wait()/.put() on them lock-free is their whole point
SYNC_CTORS = {
    "threading.Event", "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "Event", "Condition", "Semaphore",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
}

# receiver methods that mutate the container they're called on
MUTATORS = {
    "append", "appendleft", "add", "discard", "remove", "pop", "popleft",
    "clear", "update", "extend", "insert", "setdefault", "popitem",
    "put", "put_nowait",
}

# calls that block the calling thread long enough to matter under a lock
BLOCKING_DOTTED = {
    "time.sleep",
    "jax.device_get", "jax.block_until_ready",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call", "subprocess.Popen",
}
# np.asarray/np.array block only when fed a DEVICE value (then they are a
# device->host sync); on host lists/ndarrays they are cheap copies, so
# they count only when the argument looks device-resident
NP_GATHERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
DEVICEISH = re.compile(r"\b(jnp|jax)\.|\.(state|kv)\b|device")
# attribute calls that block regardless of receiver
BLOCKING_METHODS = {"item", "block_until_ready", "result", "wait"}
# gRPC service methods (backend.proto) — a stub call under a lock is the
# scrape-stall class verbatim
RPC_METHODS = {
    "Health", "Predict", "PredictStream", "LoadModel", "Embedding",
    "TokenizeString", "Status", "GetMetrics", "Rerank", "TTS",
    "SoundGeneration", "GenerateImage", "AudioTranscription",
    "PrefillPrefix", "TransferPrefix",
    "StoresSet", "StoresGet", "StoresFind", "StoresDelete",
}
# the worker-client / replica wrappers around those RPCs: blocking when
# invoked on anything that is not plain ``self`` (a method on self is a
# local computation; the same name on a replica/client object is a
# network round-trip)
CLIENT_RPC_METHODS = {
    "dial", "predict", "predict_stream", "load_model", "health",
    "prefill_prefix", "transfer_prefix", "tokenize", "embedding",
    "metrics", "stats", "rerank", "transcribe", "tts",
    "sound_generation", "generate_image",
    "stores_set", "stores_get", "stores_find", "stores_delete",
}


@dataclasses.dataclass
class Access:
    attr: str
    node: ast.AST
    write: bool
    held: frozenset       # lock names held at this point
    method: str


@dataclasses.dataclass
class BlockingCall:
    node: ast.AST
    what: str
    held: frozenset
    method: str


class ClassLockModel:
    """Per-class lock/attribute model built by one AST walk."""

    def __init__(self, module: Module, cls: ast.ClassDef):
        self.module = module
        self.cls = cls
        self.locks: set[str] = set()
        self.sync_attrs: set[str] = set()
        self.accesses: list[Access] = []
        self.blocking: list[BlockingCall] = []
        self.method_lines: dict[str, int] = {}
        # attr -> set of lock names it was written under / declared with
        self.guards: dict[str, set[str]] = {}
        self._find_locks()
        if self.locks:
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_method(fn)
            self._infer_guards()

    # -- model construction ----------------------------------------------

    def _find_locks(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = self.module.dotted(node.value.func)
            if ctor not in LOCK_CTORS and ctor not in SYNC_CTORS:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    if ctor in LOCK_CTORS:
                        self.locks.add(t.attr)
                    else:
                        self.sync_attrs.add(t.attr)

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """``self._lock`` → ``_lock`` when it names a tracked lock."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and expr.attr in self.locks):
            return expr.attr
        return None

    def _walk_method(self, fn) -> None:
        # the signature may span lines; annotations/waivers count on any
        # of them (a trailing comment naturally lands on the `:` line)
        sig_end = fn.body[0].lineno if fn.body else fn.lineno + 1
        sig_lines = range(fn.lineno, max(fn.lineno + 1, sig_end))
        self.method_lines[fn.name] = sig_lines
        held = frozenset()
        for line in sig_lines:
            held = held | self.module.guarded_by(line)
        # manual acquire()/release() of a tracked lock: treat the whole
        # method as holding it — conservative, but manual lock management
        # is rare here and the alternative is a false-positive storm
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("acquire", "release")):
                name = self._lock_name(node.func.value)
                if name:
                    held = held | {name}
        self._walk_stmts(fn.body, held, fn.name)

    def _walk_stmts(self, stmts, held: frozenset, method: str) -> None:
        for stmt in stmts:
            h = held | self.module.guarded_by(stmt.lineno)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in stmt.items:
                    name = self._lock_name(item.context_expr)
                    if name:
                        acquired.add(name)
                    else:
                        self._record_expr(item.context_expr, h, method)
                self._walk_stmts(stmt.body, h | acquired, method)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def runs LATER (thread target, callback): locks
                # held at definition time are not held at run time
                self._walk_stmts(
                    stmt.body, self.module.guarded_by(stmt.lineno),
                    method)
            elif isinstance(stmt, ast.ClassDef):
                continue
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._record_expr(stmt.iter, h, method)
                self._record_expr(stmt.target, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.While):
                self._record_expr(stmt.test, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.If):
                self._record_expr(stmt.test, h, method)
                self._walk_stmts(stmt.body + stmt.orelse, h, method)
            elif isinstance(stmt, ast.Try):
                self._walk_stmts(stmt.body, h, method)
                for hd in stmt.handlers:
                    self._walk_stmts(hd.body, h, method)
                self._walk_stmts(stmt.orelse + stmt.finalbody, h, method)
            else:
                self._record_stmt(stmt, h, method)

    def _record_stmt(self, stmt, held: frozenset, method: str) -> None:
        # classify write targets first so _record_expr can skip them
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._record_target(t, held, method)
            self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.AugAssign):
            self._record_target(stmt.target, held, method, aug=True)
            self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.AnnAssign):
            self._record_target(stmt.target, held, method)
            if stmt.value is not None:
                self._record_expr(stmt.value, held, method)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_target(t, held, method)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._record_expr(child, held, method)

    def _self_attr(self, node) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in self.locks
                and node.attr not in self.sync_attrs):
            return node.attr
        return None

    def _record_target(self, target, held, method, aug=False) -> None:
        """An assignment target: ``self.x``, ``self.x[k]``, tuples."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._record_target(e, held, method, aug=aug)
            return
        root = target
        while isinstance(root, ast.Subscript):
            self._record_expr(root.slice, held, method)
            root = root.value
        attr = self._self_attr(root)
        if attr is not None:
            self.accesses.append(
                Access(attr, target, True, held, method))
        elif isinstance(root, (ast.Attribute, ast.Name)):
            self._record_expr(root, held, method)

    def _record_expr(self, expr, held: frozenset, method: str) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._classify_call(node, held, method)
            attr = self._self_attr(node) if isinstance(
                node, ast.Attribute) else None
            if attr is None:
                continue
            parent = self.module.parents.get(node)
            write = (
                isinstance(node.ctx, (ast.Store, ast.Del))
                or (isinstance(parent, ast.Attribute)
                    and parent.attr in MUTATORS
                    and isinstance(self.module.parents.get(parent),
                                   ast.Call))
            )
            self.accesses.append(Access(attr, node, write, held, method))

    def _classify_call(self, node: ast.Call, held, method) -> None:
        if not held:
            return
        what = self._blocking_kind(node)
        if what:
            self.blocking.append(BlockingCall(node, what, held, method))

    def _blocking_kind(self, node: ast.Call) -> Optional[str]:
        name = self.module.dotted(node.func)
        if name in BLOCKING_DOTTED:
            return f"`{name}(...)`"
        if name in NP_GATHERS and node.args:
            try:
                src = ast.unparse(node.args[0])
            except Exception:
                src = ""
            if DEVICEISH.search(src):
                return f"`{name}(...)` device gather"
        func = node.func
        if not isinstance(func, ast.Attribute):
            return None
        if func.attr == "item" and not node.args and not node.keywords:
            return "`.item()` device sync"
        if func.attr in ("block_until_ready",):
            return "`.block_until_ready()` device sync"
        if func.attr in ("result", "wait"):
            return f"`.{func.attr}(...)` blocking wait"
        if func.attr in RPC_METHODS:
            return f"gRPC `.{func.attr}(...)`"
        try:
            recv = ast.unparse(func.value)
        except Exception:
            recv = ""
        if "stub" in recv.split("."):
            return f"gRPC `{recv}.{func.attr}(...)`"
        if func.attr in CLIENT_RPC_METHODS and recv != "self":
            return f"replica/worker RPC `.{func.attr}(...)`"
        return None

    # -- guard inference ---------------------------------------------------

    def _infer_guards(self) -> None:
        # explicit declarations: `self.x = ...  # jaxlint: guarded-by(_lk)`
        for node in ast.walk(self.cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            declared = self.module.guarded_by(node.lineno)
            declared = {d for d in declared if d in self.locks}
            if not declared:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                root = t
                while isinstance(root, ast.Subscript):
                    root = root.value
                attr = self._self_attr(root)
                if attr:
                    self.guards.setdefault(attr, set()).update(declared)
        # inferred: written under a held lock outside __init__
        for a in self.accesses:
            if a.write and a.held and a.method != "__init__":
                self.guards.setdefault(a.attr, set()).update(a.held)


def method_waived(module: Module, model: ClassLockModel,
                  method: str, rule: str) -> bool:
    """A ``# jaxlint: disable=<rule>`` on a METHOD's ``def`` line waives
    the whole body — the idiom for single-owner-thread structures where
    every lock-free access in the method is the same deliberate design
    (one documented waiver instead of one per line)."""
    for line in model.method_lines.get(method, ()):
        m = SUPPRESS_RE.search(module.line_text(line))
        if not m:
            continue
        ids = {p.strip() for p in m.group(1).split(",")}
        if "all" in ids or rule in ids:
            return True
    return False


def lock_models(module: Module) -> list[ClassLockModel]:
    cached = module.__dict__.get("_lock_models")
    if cached is None:
        cached = [
            ClassLockModel(module, node)
            for node in ast.walk(module.tree)
            if isinstance(node, ast.ClassDef)
        ]
        module.__dict__["_lock_models"] = cached
    return cached


class LockGuardedAttr:
    """Reads/writes of a lock-guarded attribute without the lock.

    An attribute written under ``with self._lock`` anywhere (or declared
    with ``guarded-by``) is shared mutable state; touching it lock-free
    in another method is a data race until proven otherwise. Intentional
    lock-free reads (host mirrors, monotone counters feeding a scrape)
    get an inline ``disable`` with the reason spelled out.
    """

    id = "lock-guarded-attr"
    doc = ("read/write of an attribute guarded by a class lock "
           "(written under `with self._lock` elsewhere) while the lock "
           "is not held")

    def check(self, module: Module) -> Iterator[Finding]:
        for model in lock_models(module):
            for a in model.accesses:
                guard = model.guards.get(a.attr)
                if not guard or a.method == "__init__":
                    continue
                if a.held & guard:
                    continue
                if method_waived(module, model, a.method, self.id):
                    continue
                kind = "write to" if a.write else "read of"
                lock = "/".join(sorted(guard))
                yield module.finding(
                    a.node, self.id,
                    f"{kind} '{a.attr}' outside `self.{lock}` — it is "
                    f"written under that lock elsewhere in "
                    f"{model.cls.name}; take the lock, or waive with a "
                    f"reason if the lock-free access is intentional",
                )


class BlockingUnderLock:
    """Blocking operations while holding a class lock.

    A device round-trip, replica/worker RPC, future/event wait, or
    ``time.sleep`` under a lock blocks every thread that needs the lock
    for the call's full duration — the PR 7 scrape stall (stats RPCs
    under the manager lock) as a lint rule. Copy what the call needs,
    release the lock, then block.
    """

    id = "blocking-under-lock"
    doc = ("device sync, gRPC/replica RPC, future/event wait, "
           "subprocess, or time.sleep performed while a threading lock "
           "is held")

    def check(self, module: Module) -> Iterator[Finding]:
        for model in lock_models(module):
            for b in model.blocking:
                if method_waived(module, model, b.method, self.id):
                    continue
                lock = "/".join(sorted(b.held))
                yield module.finding(
                    b.node, self.id,
                    f"{b.what} while holding `self.{lock}` in "
                    f"{model.cls.name}.{b.method} blocks every thread "
                    f"needing the lock; move the call outside the "
                    f"critical section",
                )
