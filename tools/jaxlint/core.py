"""jaxlint engine: AST module model, findings, suppressions, baseline.

Rules are plain objects with an ``id``, a ``doc`` string, and a
``check(module)`` generator yielding :class:`Finding`. The engine owns
everything rule-agnostic: file discovery, parsing, the parent map,
inline-suppression filtering, and the baseline protocol.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path, PurePosixPath
from typing import Iterable, Iterator, Optional, Protocol

SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_\-, ]+)")
# `# jaxlint: guarded-by(_lock)` — a lock-discipline assertion consumed by
# the lockcheck pass: on a `def` line it means "callers hold <lock>", on an
# attribute-init line it declares the attribute guarded, on any other
# statement it asserts the statement runs with <lock> held.
GUARDED_RE = re.compile(r"#\s*jaxlint:\s*guarded-by\(([A-Za-z0-9_, ]+)\)")

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, addressable as ``file:line:col: rule-id``."""

    file: str       # posix-style path as scanned (baseline key component)
    line: int       # 1-based
    col: int        # 0-based
    rule: str
    message: str
    text: str       # stripped source line (line-number-stable baseline key)

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule(Protocol):
    id: str
    doc: str

    def check(self, module: "Module") -> Iterator[Finding]: ...


class ProjectRule(Protocol):
    """A rule that needs the WHOLE scanned file set before it can judge
    (cross-file consistency, e.g. the metric-name registry check). The
    engine feeds every parsed module to ``collect`` and asks for findings
    once at the end. Instances are stateful per run — the engine
    constructs a fresh one from the registered instance's class."""

    id: str
    doc: str

    def collect(self, module: "Module") -> None: ...

    def finalize(self) -> Iterator[Finding]: ...


class Module:
    """Parsed file + the shared indexes every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = normalize_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # alias map for names imported from jax: {"jit": "jax.jit", ...}
        self.jax_aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax" or node.module.startswith("jax.")
            ):
                for a in node.names:
                    self.jax_aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}"
                    )
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        self.jax_aliases[a.asname or a.name] = a.name

    # -- navigation helpers ----------------------------------------------

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, _SCOPES):
                return anc
        return None

    def in_loop(self, node: ast.AST) -> bool:
        """Inside a for/while, not crossing a function boundary (a nested
        def's hotness is judged by its own name, not its definition site)."""
        for anc in self.ancestors(node):
            if isinstance(anc, _SCOPES):
                return False
            if isinstance(anc, _LOOPS):
                return True
        return False

    def dotted(self, node: ast.AST) -> Optional[str]:
        """'jax.random.normal'-style name for a Name/Attribute chain,
        with jax import aliases resolved at the root (``from jax import
        jit`` makes bare ``jit`` resolve to ``jax.jit``)."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.jax_aliases.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node: ast.AST, rule: str, message: str) -> Finding:
        return Finding(
            file=self.path,
            line=node.lineno,
            col=node.col_offset,
            rule=rule,
            message=message,
            text=self.line_text(node.lineno),
        )

    # -- suppressions / annotations --------------------------------------

    def suppressed(self, finding: Finding) -> bool:
        m = SUPPRESS_RE.search(self.line_text(finding.line))
        if not m:
            return False
        ids = {part.strip() for part in m.group(1).split(",")}
        return "all" in ids or finding.rule in ids

    def guarded_by(self, lineno: int) -> frozenset:
        """Lock names asserted held by a ``guarded-by(...)`` annotation
        on ``lineno`` (empty when unannotated)."""
        m = GUARDED_RE.search(self.line_text(lineno))
        if not m:
            return frozenset()
        return frozenset(
            p.strip() for p in m.group(1).split(",") if p.strip()
        )


class Baseline:
    """Checked-in record of accepted pre-existing findings.

    Entries are keyed on (file, rule, stripped source text) with a
    count, NOT on line numbers — unrelated edits that shift lines don't
    invalidate the baseline, while any change to a flagged line itself
    surfaces the finding again.
    """

    def __init__(self, entries: Optional[dict[tuple, int]] = None):
        self.entries = entries or {}

    @staticmethod
    def key(f: Finding) -> tuple:
        return (f.file, f.rule, f.text)

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        data = json.loads(Path(path).read_text())
        entries: dict[tuple, int] = {}
        for e in data.get("entries", []):
            k = (e["file"], e["rule"], e["text"])
            entries[k] = entries.get(k, 0) + int(e.get("count", 1))
        return cls(entries)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        entries: dict[tuple, int] = {}
        for f in findings:
            if f.rule == "parse-error":
                continue  # never accept an unscannable file as baseline
            k = cls.key(f)
            entries[k] = entries.get(k, 0) + 1
        return cls(entries)

    def write(self, path: Path) -> None:
        entries = [
            {"file": f, "rule": r, "text": t, "count": c}
            for (f, r, t), c in sorted(self.entries.items())
        ]
        Path(path).write_text(
            json.dumps({"version": 1, "entries": entries}, indent=2)
            + "\n"
        )

    def filter(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[tuple]]:
        """(new findings not covered by the baseline, stale entries)."""
        budget = dict(self.entries)
        new: list[Finding] = []
        for f in findings:
            k = self.key(f)
            # parse errors are never absorbable: a file the linter can't
            # scan must fail the run even if an old baseline has the key
            if f.rule != "parse-error" and budget.get(k, 0) > 0:
                budget[k] -= 1
            else:
                new.append(f)
        stale = [k for k, c in budget.items() if c > 0]
        return new, stale


def normalize_path(path: str) -> str:
    """Posix path relative to cwd when possible — so findings (and the
    baseline keys derived from them) are stable whether the CLI was
    invoked with relative or absolute paths."""
    p = Path(path)
    try:
        p = p.resolve().relative_to(Path.cwd())
    except ValueError:
        pass  # outside cwd: keep as given
    return str(PurePosixPath(p.as_posix()))


def iter_py_files(paths: Iterable[str]) -> Iterator[Path]:
    for p in paths:
        # resolve '.'/'..' segments up front so the hidden-dir filter
        # below never discards a legitimate parent-relative target
        path = Path(p).resolve()
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if any(part.startswith(".") or part == "__pycache__"
                       for part in f.parts[len(path.parts):]):
                    continue
                yield f
        elif path.suffix == ".py":
            yield path


def load_module(path: Path) -> "Module | Finding":
    """Parse one file → Module, or the parse-error Finding."""
    try:
        return Module(str(path), path.read_text())
    except (SyntaxError, UnicodeDecodeError) as e:
        line = getattr(e, "lineno", 1) or 1
        return Finding(
            file=normalize_path(str(path)), line=line, col=0,
            rule="parse-error", message=f"could not parse: {e}", text="",
        )


def lint_file(path: Path, rules: Iterable[Rule]) -> list[Finding]:
    """Run the per-module rules against one file. ProjectRules (which
    need the whole scanned file set) are skipped — only lint_paths can
    meaningfully run those."""
    module = load_module(path)
    if isinstance(module, Finding):
        return [module]
    out: list[Finding] = []
    for rule in rules:
        if not hasattr(rule, "check"):
            continue
        for f in rule.check(module):
            if not module.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return out


def lint_paths(
    paths: Iterable[str], rules: Optional[Iterable[Rule]] = None
) -> list[Finding]:
    if rules is None:
        from tools.jaxlint.rules import ALL_RULES
        rules = ALL_RULES
    rules = list(rules)
    per_module = [r for r in rules if not hasattr(r, "collect")]
    # project rules accumulate cross-file state: a fresh instance per run
    # so repeated lint_paths calls in one process never bleed together
    project = [type(r)() for r in rules if hasattr(r, "collect")]
    findings: list[Finding] = []
    modules: dict[str, Module] = {}
    for f in iter_py_files(paths):
        module = load_module(f)
        if isinstance(module, Finding):
            findings.append(module)
            continue
        for rule in per_module:
            for fd in rule.check(module):
                if not module.suppressed(fd):
                    findings.append(fd)
        for rule in project:
            rule.collect(module)
        modules[module.path] = module
    for rule in project:
        for fd in rule.finalize():
            m = modules.get(fd.file)
            if m is None or not m.suppressed(fd):
                findings.append(fd)
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings
