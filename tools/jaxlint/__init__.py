"""jaxlint — JAX-aware static analysis for the localai_tpu serving stack.

Generic linters see Python; they don't see XLA. The failure modes that
actually take this stack down are JAX-shaped: a host sync hidden in a
decode loop, a ``jax.jit`` that re-traces per call, Python control flow
branching on a tracer, a PRNG key consumed twice, or a ``jax.config``
option that the installed JAX no longer accepts (the bug that once made
the whole test suite fail at conftest import). jaxlint is a small
AST-rule engine that encodes those failure modes as checkable rules.

Usage::

    python -m tools.jaxlint localai_tpu tests
    python -m tools.jaxlint --list-rules
    python -m tools.jaxlint --write-baseline localai_tpu tests

Findings print as ``file:line:col: rule-id message``. Suppress a single
line with ``# jaxlint: disable=<rule-id>`` (comma-separated ids, or
``all``). Pre-existing findings live in ``tools/jaxlint/baseline.json``
so CI only fails on NEW findings; regenerate it with
``--write-baseline`` after an intentional change.
"""

from tools.jaxlint.core import Baseline, Finding, lint_paths
from tools.jaxlint.rules import ALL_RULES

__all__ = ["ALL_RULES", "Baseline", "Finding", "lint_paths"]
