"""Project-wide call graph + blocking-taint propagation.

The single-module passes (lockcheck, the hot-path rules) judge one
function body at a time, so one level of helper indirection hides a
violation: ``async def handler`` calling ``_encode_png`` which calls
``Image.fromarray(...).save(...)`` looks clean to a body-local scan.
This module builds the graph those passes need:

  * a **function index** over every scanned module — module-level
    ``def``s and class methods, keyed ``path::Class.name``;
  * **call edges** resolved the two ways this codebase actually calls
    its own code: module-level names (including ``from x import f`` and
    ``import x as y; y.f(...)``) and ``self.method(...)`` (with
    one-hop base-class lookup inside the same module);
  * **blocking classification** of leaf calls — device round-trips,
    ``time.sleep``, gRPC/replica RPCs, subprocess, file and PIL I/O,
    lock acquires and future/event waits — each tagged with the
    *domains* it matters for (``async``: stalls the event loop;
    ``lock``: stalls every thread needing a held lock);
  * **taint propagation**: a function is blocking-tainted when its own
    scope contains a blocking leaf or it (synchronously) calls a
    tainted project function. The witness chain is kept so findings can
    say *why* (``helper → _encode_png → PIL Image.fromarray``).

Scope walks never descend into nested ``def``/``lambda``: a closure
passed to ``run_in_executor``/``to_thread`` runs OFF the calling
context, which is exactly why the offload idiom is written that way.
A call that is itself directly awaited is skipped too — ``await
lock.acquire()`` is the asyncio primitive, not the blocking one.

The ``# jaxlint: offloaded`` annotation is the escape hatch for code
the graph cannot see runs off-loop: on a ``def`` line it marks the
whole function as executor-side (never taints, body never flagged by
the loop rules); on any other line it clears that line's blocking
leaves. Always written with the reason: ``# jaxlint: offloaded (runs
via state.executor only)``.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator, Optional

from tools.jaxlint.core import Module

OFFLOADED_RE = re.compile(r"#\s*jaxlint:\s*offloaded\b")

# -- the shared blocking-leaf vocabulary (lockcheck imports these) ----------

# calls that block the calling thread long enough to matter under a lock
# or on the event loop
BLOCKING_DOTTED = {
    "time.sleep",
    "jax.device_get", "jax.block_until_ready",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call", "subprocess.Popen",
}
# np.asarray/np.array block only when fed a DEVICE value (then they are a
# device->host sync); on host lists/ndarrays they are cheap copies, so
# they count only when the argument looks device-resident
NP_GATHERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
DEVICEISH = re.compile(r"\b(jnp|jax)\.|\.(state|kv)\b|device")
# attribute calls that block regardless of receiver
BLOCKING_METHODS = {"item", "block_until_ready", "result", "wait"}
# gRPC service methods (backend.proto) — a stub call under a lock is the
# scrape-stall class verbatim
RPC_METHODS = {
    "Health", "Predict", "PredictStream", "LoadModel", "Embedding",
    "TokenizeString", "Status", "GetMetrics", "Rerank", "TTS",
    "SoundGeneration", "GenerateImage", "AudioTranscription",
    "PrefillPrefix", "TransferPrefix",
    "StoresSet", "StoresGet", "StoresFind", "StoresDelete",
}
# the worker-client / replica wrappers around those RPCs: blocking when
# invoked on anything that is not plain ``self`` (a method on self is a
# local computation; the same name on a replica/client object is a
# network round-trip)
CLIENT_RPC_METHODS = {
    "dial", "predict", "predict_stream", "load_model", "health",
    "prefill_prefix", "transfer_prefix", "tokenize", "embedding",
    "metrics", "stats", "rerank", "transcribe", "tts",
    "sound_generation", "generate_image",
    "stores_set", "stores_get", "stores_find", "stores_delete",
}

# event-loop-only leaves: disk and image-codec work is milliseconds-to-
# hundreds-of-ms — fatal on the loop, but not the lockcheck noise class
# (a config read under a startup lock is fine)
PIL_RE = re.compile(r"(^|\.)Image\.(open|fromarray|frombytes|new)$")
FILE_METHODS = {"read_text", "read_bytes", "write_text", "write_bytes"}
# image/array payloads whose np materialization is either a device pull
# or a multi-MB host copy — both loop-fatal (extends DEVICEISH for the
# async domain only)
PAYLOADISH = re.compile(
    r"\b(jnp|jax)\.|\.(state|kv)\b|device|\bimg\b|image|audio|wav|frame")

# a call whose callable argument escapes to a worker thread: the call
# itself is the offload, never a blocking leaf
OFFLOADER_SUFFIXES = ("run_in_executor", "to_thread", "_in_executor")

_SYNC_DOMAINS = frozenset({"async", "lock"})
_ASYNC_ONLY = frozenset({"async"})


@dataclasses.dataclass
class BlockingSite:
    node: ast.Call
    desc: str
    domains: frozenset


@dataclasses.dataclass
class CallEdge:
    node: ast.Call
    callee: str         # FuncNode key
    awaited: bool


@dataclasses.dataclass
class FuncNode:
    key: str            # "<module.path>::<qualname>"
    qualname: str       # "name" or "Class.name"
    module: Module
    node: ast.AST       # FunctionDef | AsyncFunctionDef
    cls: Optional[str]
    is_async: bool
    offloaded: bool     # `# jaxlint: offloaded` on a signature line
    sites: list = dataclasses.field(default_factory=list)   # [BlockingSite]
    edges: list = dataclasses.field(default_factory=list)   # [CallEdge]
    is_generator: bool = False


def signature_lines(module: Module, fn) -> range:
    """The def's signature may span lines; annotations count on any of
    them (a trailing comment naturally lands on the ``:`` line)."""
    sig_end = fn.body[0].lineno if fn.body else fn.lineno + 1
    return range(fn.lineno, max(fn.lineno + 1, sig_end))


def is_offloaded_def(module: Module, fn) -> bool:
    return any(OFFLOADED_RE.search(module.line_text(line))
               for line in signature_lines(module, fn))


def own_scope(fn) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested def/lambda —
    nested callables run in another context (thread target, executor
    closure, later callback), never inline."""
    nested = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    stack = list(fn.body) if hasattr(fn, "body") else [fn]
    while stack:
        node = stack.pop()
        if isinstance(node, nested):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def classify_blocking(module: Module, node: ast.Call,
                      deviceish: re.Pattern = DEVICEISH
                      ) -> Optional[tuple[str, frozenset]]:
    """(description, domains) when ``node`` is a blocking leaf call."""
    name = module.dotted(node.func)
    if name in BLOCKING_DOTTED:
        return f"`{name}(...)`", _SYNC_DOMAINS
    if name in NP_GATHERS and node.args:
        try:
            src = ast.unparse(node.args[0])
        except Exception:
            src = ""
        if deviceish.search(src):
            return f"`{name}(...)` device/payload gather", _SYNC_DOMAINS
    if name and PIL_RE.search(name):
        return f"PIL `{name}(...)` image decode/encode", _ASYNC_ONLY
    if name == "open":
        return "`open(...)` file I/O", _ASYNC_ONLY
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "item" and not node.args and not node.keywords:
        return "`.item()` device sync", _SYNC_DOMAINS
    if func.attr == "block_until_ready":
        return "`.block_until_ready()` device sync", _SYNC_DOMAINS
    if func.attr in ("result", "wait"):
        return f"`.{func.attr}(...)` blocking wait", _SYNC_DOMAINS
    if func.attr == "acquire":
        # a NON-awaited acquire in async context is either a threading
        # lock (blocks the loop) or a forgotten-await asyncio acquire —
        # both findings. lockcheck models held locks separately.
        return "`.acquire(...)` lock wait", _ASYNC_ONLY
    if func.attr in FILE_METHODS:
        return f"`.{func.attr}(...)` file I/O", _ASYNC_ONLY
    if func.attr in RPC_METHODS:
        return f"gRPC `.{func.attr}(...)`", _SYNC_DOMAINS
    try:
        recv = ast.unparse(func.value)
    except Exception:
        recv = ""
    if "stub" in recv.split("."):
        return f"gRPC `{recv}.{func.attr}(...)`", _SYNC_DOMAINS
    if func.attr in CLIENT_RPC_METHODS and recv != "self":
        return f"replica/worker RPC `.{func.attr}(...)`", _SYNC_DOMAINS
    return None


def is_offloader(module: Module, node: ast.Call) -> bool:
    name = module.dotted(node.func) or ""
    return name.endswith(OFFLOADER_SUFFIXES)


# the sharded-producer vocabulary (shared with shardcheck's deep pass)
SHARDED_SRC = re.compile(
    r"\b(shard_map\s*\(|NamedSharding\s*\(|device_put\s*\(.*"
    r"(named\s*\(|NamedSharding\s*\(|P\s*\())")


class CallGraph:
    """One graph over the whole scanned module set. Build with
    :func:`build_graph` — repeated project rules in one run share the
    instance (it is cached on the Module objects themselves, so there
    is no cross-run staleness)."""

    def __init__(self, modules: list[Module]):
        self.modules = list(modules)
        self.key = frozenset(m.path for m in self.modules)
        self.functions: dict[str, FuncNode] = {}
        # per module: name -> key for top-level defs
        self._top: dict[str, dict[str, str]] = {}
        # per module: class -> {method -> key}, class -> [base names]
        self._methods: dict[str, dict[str, dict[str, str]]] = {}
        self._bases: dict[str, dict[str, list[str]]] = {}
        # dotted module name suffix -> path ("" on ambiguity)
        self._mod_by_dotted: dict[str, str] = {}
        # per module: alias -> ("mod", path) | ("func", key)
        self._imports: dict[str, dict[str, tuple]] = {}
        self._taint_memo: dict[tuple, Optional[list]] = {}
        self._sharded_memo: dict[str, bool] = {}
        for m in self.modules:
            self._index_module(m)
        for m in self.modules:
            self._index_imports(m)
        for fn in self.functions.values():
            self._scan_body(fn)

    # -- indexing ----------------------------------------------------------

    def _index_module(self, m: Module) -> None:
        top: dict[str, str] = {}
        methods: dict[str, dict[str, str]] = {}
        bases: dict[str, list[str]] = {}
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{m.path}::{node.name}"
                top[node.name] = key
                self._add_func(key, node.name, m, node, None)
            elif isinstance(node, ast.ClassDef):
                per: dict[str, str] = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        qual = f"{node.name}.{sub.name}"
                        key = f"{m.path}::{qual}"
                        per[sub.name] = key
                        self._add_func(key, qual, m, sub, node.name)
                methods[node.name] = per
                bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)
                ]
        self._top[m.path] = top
        self._methods[m.path] = methods
        self._bases[m.path] = bases
        # register every dotted suffix of the path so absolute imports
        # resolve whether the scan root is the repo or a tmp fixture tree
        parts = m.path.replace("\\", "/").split("/")
        parts[-1] = parts[-1][:-3]  # strip .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        for i in range(len(parts)):
            dotted = ".".join(parts[i:])
            if not dotted:
                continue
            if dotted in self._mod_by_dotted \
                    and self._mod_by_dotted[dotted] != m.path:
                self._mod_by_dotted[dotted] = ""  # ambiguous suffix
            else:
                self._mod_by_dotted[dotted] = m.path

    def _add_func(self, key, qualname, m, node, cls) -> None:
        self.functions[key] = FuncNode(
            key=key, qualname=qualname, module=m, node=node, cls=cls,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            offloaded=is_offloaded_def(m, node),
            is_generator=any(isinstance(n, (ast.Yield, ast.YieldFrom))
                             for n in own_scope(node)),
        )

    def _module_path(self, dotted: str) -> Optional[str]:
        hit = self._mod_by_dotted.get(dotted)
        return hit or None

    def _index_imports(self, m: Module) -> None:
        imp: dict[str, tuple] = {}
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    path = self._module_path(a.name)
                    if path:
                        imp[a.asname or a.name.split(".")[0]] = \
                            ("mod", path) if a.asname else ("pkg", a.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = self._module_path(node.module)
                for a in node.names:
                    # `from pkg import mod` vs `from mod import func`
                    sub = self._module_path(f"{node.module}.{a.name}")
                    if sub:
                        imp[a.asname or a.name] = ("mod", sub)
                    elif base and a.name in self._top.get(base, {}):
                        imp[a.asname or a.name] = (
                            "func", self._top[base][a.name])
        self._imports[m.path] = imp

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, module: Module, cls: Optional[str],
                     node: ast.Call) -> Optional[str]:
        """FuncNode key for a call on a module-level name, an imported
        project module's attribute, or ``self.method``."""
        func = node.func
        imp = self._imports.get(module.path, {})
        if isinstance(func, ast.Name):
            hit = self._top.get(module.path, {}).get(func.id)
            if hit:
                return hit
            tag = imp.get(func.id)
            if tag and tag[0] == "func":
                return tag[1]
            return None
        if not isinstance(func, ast.Attribute):
            return None
        # self.method(...) — own class, then one-hop same-module bases
        if isinstance(func.value, ast.Name) and func.value.id == "self" \
                and cls is not None:
            methods = self._methods.get(module.path, {})
            hit = methods.get(cls, {}).get(func.attr)
            if hit:
                return hit
            for base in self._bases.get(module.path, {}).get(cls, ()):
                hit = methods.get(base, {}).get(func.attr)
                if hit:
                    return hit
            return None
        # alias.func(...) — `import localai_tpu.api.openai as oai` or
        # `from localai_tpu.api import openai`
        if isinstance(func.value, ast.Name):
            tag = imp.get(func.value.id)
            if tag and tag[0] == "mod":
                return self._top.get(tag[1], {}).get(func.attr)
            return None
        # fully dotted: localai_tpu.api.openai.func(...)
        dotted = module.dotted(func)
        if dotted and "." in dotted:
            mod, _, fname = dotted.rpartition(".")
            path = self._module_path(mod)
            if path:
                return self._top.get(path, {}).get(fname)
        return None

    # -- body scan ---------------------------------------------------------

    def _scan_body(self, fn: FuncNode) -> None:
        m = fn.module
        for node in own_scope(fn.node):
            if not isinstance(node, ast.Call):
                continue
            parent = m.parents.get(node)
            awaited = isinstance(parent, ast.Await)
            callee = self.resolve_call(m, fn.cls, node)
            if callee is not None:
                fn.edges.append(CallEdge(node, callee, awaited))
                continue
            if awaited or is_offloader(m, node):
                continue
            if OFFLOADED_RE.search(m.line_text(node.lineno)):
                continue
            hit = classify_blocking(m, node, deviceish=PAYLOADISH)
            if hit:
                fn.sites.append(BlockingSite(node, hit[0], hit[1]))

    # -- taint -------------------------------------------------------------

    def taint(self, key: str, domain: str = "async",
              _stack: Optional[frozenset] = None) -> Optional[list[str]]:
        """Witness chain (labels ending in the blocking desc) when the
        function's own scope — or, transitively, a synchronously-called
        project helper's — contains a blocking leaf in ``domain``.
        ``None`` when clean. Offloaded functions never taint."""
        memo_key = (key, domain)
        if memo_key in self._taint_memo:
            return self._taint_memo[memo_key]
        fn = self.functions.get(key)
        if fn is None or fn.offloaded:
            self._taint_memo[memo_key] = None
            return None
        stack = _stack or frozenset()
        if key in stack:
            return None  # recursion: judged by the outer frame
        for s in fn.sites:
            if domain in s.domains:
                self._taint_memo[memo_key] = [s.desc]
                return [s.desc]
        for e in fn.edges:
            callee = self.functions.get(e.callee)
            if callee is None or callee.is_async or e.awaited:
                continue  # an awaited/async callee is judged on its own
            sub = self.taint(e.callee, domain, stack | {key})
            if sub is not None:
                chain = [callee.qualname] + sub
                self._taint_memo[memo_key] = chain
                return chain
        self._taint_memo[memo_key] = None
        return None

    def call_taint(self, module: Module, cls: Optional[str],
                   node: ast.Call, domain: str = "async"
                   ) -> Optional[list[str]]:
        """Taint chain for a concrete call site, or None."""
        key = self.resolve_call(module, cls, node)
        if key is None:
            return None
        fn = self.functions[key]
        if fn.is_async:
            return None
        sub = self.taint(key, domain)
        return [fn.qualname] + sub if sub is not None else None

    # -- sharded returns (shardcheck's deep pass) --------------------------

    def returns_sharded(self, key: str,
                        _stack: Optional[frozenset] = None) -> bool:
        """True when the function returns a value produced by shard_map /
        NamedSharding placement — directly, via a local, or via a call to
        another sharded-returning project function."""
        if key in self._sharded_memo:
            return self._sharded_memo[key]
        fn = self.functions.get(key)
        if fn is None:
            return False
        stack = _stack or frozenset()
        if key in stack:
            return False
        sharded_locals: set[str] = set()
        for node in own_scope(fn.node):
            if isinstance(node, ast.Assign):
                try:
                    src = ast.unparse(node.value)
                except Exception:
                    continue
                produced = bool(SHARDED_SRC.search(src))
                if not produced and isinstance(node.value, ast.Call):
                    callee = self.resolve_call(
                        fn.module, fn.cls, node.value)
                    produced = callee is not None and self.returns_sharded(
                        callee, stack | {key})
                if produced:
                    for t in node.targets:
                        elts = (t.elts if isinstance(t, (ast.Tuple,
                                                         ast.List))
                                else [t])
                        sharded_locals.update(
                            e.id for e in elts if isinstance(e, ast.Name))
        out = False
        for node in own_scope(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Name) and v.id in sharded_locals:
                out = True
            elif isinstance(v, ast.Call):
                callee = self.resolve_call(fn.module, fn.cls, v)
                if callee is not None and self.returns_sharded(
                        callee, stack | {key}):
                    out = True
            else:
                try:
                    if SHARDED_SRC.search(ast.unparse(v)):
                        out = True
                except Exception:
                    pass
            if out:
                break
        self._sharded_memo[key] = out
        return out

    def sharded_producer_names(self, module: Module,
                               cls: Optional[str]) -> set[str]:
        """Top-level/function names IN SCOPE of ``module`` that resolve
        to sharded-returning project functions (used to extend the
        per-scope dataflow in shardcheck)."""
        out: set[str] = set()
        for name, key in self._top.get(module.path, {}).items():
            if self.returns_sharded(key):
                out.add(name)
        for alias, tag in self._imports.get(module.path, {}).items():
            if tag[0] == "func" and self.returns_sharded(tag[1]):
                out.add(alias)
        return out


def build_graph(modules: list[Module]) -> CallGraph:
    """Build (or reuse) the CallGraph for a module set. The instance is
    cached on the Module objects: several project rules in one
    lint_paths run receive the SAME Module objects, so they share one
    graph; fresh parses (the next run) never see a stale one."""
    modules = list(modules)
    if not modules:
        return CallGraph([])
    key = frozenset(m.path for m in modules)
    cached = modules[0].__dict__.get("_callgraph")
    if (cached is not None and cached.key == key
            and all(m.__dict__.get("_callgraph") is cached
                    for m in modules)):
        return cached
    graph = CallGraph(modules)
    for m in modules:
        m.__dict__["_callgraph"] = graph
    return graph
