"""The JAX-specific rule set.

Each rule is a small class with ``id``, ``doc`` and a ``check(module)``
generator. Rules are deliberately heuristic — they optimize for the
failure modes this serving stack has actually hit, and anything
intentional is one inline ``# jaxlint: disable=<rule>`` away.
"""

from __future__ import annotations

import ast
import difflib
import re
from typing import Iterator, Optional

from tools.jaxlint.core import _LOOPS, _SCOPES, SUPPRESS_RE, Finding, Module


# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------

class HostSyncInHotPath:
    """Device→host synchronization inside the serving hot path.

    Every ``.item()``, ``int()``/``float()`` on an array,
    ``np.asarray``/``np.array`` on a device value, or
    ``jax.device_get`` blocks the host until the device queue drains —
    inside a decode/step loop that de-pipelines the whole engine. Hot
    scope is any loop body or step/decode/drain/consume/run-named
    function in the engine and worker-serving modules, plus any direct
    host materialization of the device-resident serving state
    (``self.state`` / ``self.kv``) anywhere in those files.
    """

    id = "host-sync-in-hot-path"
    doc = ("device→host sync (.item(), int()/float() on arrays, "
           "np.asarray, jax.device_get) inside an engine decode/step "
           "hot path")

    HOT_FILES = (
        re.compile(r"(^|/)localai_tpu/engine/[^/]+\.py$"),
        re.compile(r"(^|/)localai_tpu/worker/serving\.py$"),
    )
    HOT_FUNC = re.compile(r"(^|_)(step|decode|drain|consume|run|spec)(_|$|\d)")
    NP_SYNCS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
    # attribute chains rooting in device-resident serving state
    STATE_ROOT = re.compile(r"^self\.([A-Za-z_]+\.)?(state|kv)\b")

    def check(self, module: Module) -> Iterator[Finding]:
        if not any(p.search(module.path) for p in self.HOT_FILES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = self._classify(module, node)
            if hit is None:
                continue
            what, arg = hit
            if self._hot_scope(module, node) or self._on_state(module, arg):
                yield module.finding(
                    node, self.id,
                    f"{what} forces a device→host sync in a decode/step "
                    f"hot path; keep the value on device or use the "
                    f"async/batched host APIs",
                )

    def _classify(self, module, node):
        """(description, sync-argument-or-None) for sync-shaped calls."""
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "item"
                and not node.args and not node.keywords):
            return f"`{ast.unparse(node)}`", func.value
        name = module.dotted(func)
        if name in self.NP_SYNCS or name == "jax.device_get":
            return (f"`{name}(...)`",
                    node.args[0] if node.args else None)
        if (isinstance(func, ast.Name) and func.id in ("int", "float")
                and len(node.args) == 1 and not node.keywords
                and not isinstance(node.args[0], ast.Constant)):
            arg = node.args[0]
            if self._arraylike(module, node, arg):
                return f"`{func.id}()` on an array", arg
            return None
        return None

    def _hot_scope(self, module, node) -> bool:
        fn = module.enclosing_function(node)
        fn_name = getattr(fn, "name", "")
        return bool(self.HOT_FUNC.search(fn_name)) or module.in_loop(node)

    def _on_state(self, module, arg) -> bool:
        if arg is None:
            return False
        try:
            return bool(self.STATE_ROOT.match(ast.unparse(arg)))
        except Exception:
            return False

    def _arraylike(self, module, node, arg) -> bool:
        """Heuristic: the int()/float() argument is device-resident —
        rooted in serving state, textually a jax/jnp expression, or a
        local assigned from one inside the same function."""
        src = ast.unparse(arg)
        if self.STATE_ROOT.match(src) or re.search(r"\b(jnp|jax)\.", src):
            return True
        root = arg
        while isinstance(root, (ast.Subscript, ast.Attribute)):
            root = root.value
        if not isinstance(root, ast.Name):
            return False
        fn = module.enclosing_function(node)
        if fn is None or isinstance(fn, ast.Lambda):
            return False
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == root.id
                or isinstance(t, ast.Tuple) and any(
                    isinstance(e, ast.Name) and e.id == root.id
                    for e in t.elts)
                for t in n.targets
            ):
                vsrc = ast.unparse(n.value)
                if (re.search(r"\b(jnp|jax)\.", vsrc)
                        or self.STATE_ROOT.match(vsrc)):
                    return True
        return False


# ---------------------------------------------------------------------------
# jit-in-loop
# ---------------------------------------------------------------------------

class JitInLoop:
    """``jax.jit``/``pjit`` invoked per iteration or per call.

    ``jax.jit`` returns a NEW compiled-function wrapper whose cache dies
    with it; constructing one inside a loop (or immediately invoking it,
    ``jax.jit(f)(x)``) re-traces and re-compiles on every pass instead
    of once. Hoist the ``jit`` to definition/init time.
    """

    id = "jit-in-loop"
    doc = ("jax.jit/pjit constructed inside a loop or immediately "
           "invoked — a fresh compile cache per call")

    JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}

    def check(self, module: Module) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = module.dotted(node.func)
            if name not in self.JIT_NAMES:
                continue
            parent = module.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield module.finding(
                    node, self.id,
                    f"`{name}(...)` is immediately invoked — the "
                    f"compiled function (and its cache) is discarded "
                    f"after one call; hoist the jit out",
                )
            elif module.in_loop(node):
                yield module.finding(
                    node, self.id,
                    f"`{name}(...)` inside a loop builds a fresh "
                    f"compile cache every iteration; jit once outside "
                    f"the loop",
                )


# ---------------------------------------------------------------------------
# tracer-control-flow
# ---------------------------------------------------------------------------

class TracerControlFlow:
    """Python ``if``/``while`` on traced array values inside ``@jit``.

    Under trace, array-valued conditions raise ConcretizationTypeError
    at best and silently bake in one branch at worst. Shape/dtype/ndim
    checks, ``is None`` tests, ``isinstance``/``len`` and
    ``static_argnames`` parameters are static and fine; anything else
    needs ``jnp.where``/``lax.cond``/``lax.while_loop``.
    """

    id = "tracer-control-flow"
    doc = ("Python if/while branching on a traced array value inside a "
           "@jit-decorated function")

    STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
    STATIC_CALLS = {"isinstance", "len"}

    def check(self, module: Module) -> Iterator[Finding]:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            statics = self._jit_statics(module, fn)
            if statics is None:
                continue
            args = fn.args
            params = [a.arg for a in (
                args.posonlyargs + args.args + args.kwonlyargs)]
            traced = {p for p in params if p not in statics
                      and p not in ("self", "cls")}
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    bad = self._traced_name_in_test(module, node.test, traced)
                    if bad:
                        kind = "if" if isinstance(node, ast.If) else "while"
                        yield module.finding(
                            node, self.id,
                            f"`{kind}` branches on traced argument "
                            f"'{bad}' inside a @jit function; use "
                            f"jnp.where / lax.cond / lax.while_loop (or "
                            f"mark it static)",
                        )

    def _jit_statics(self, module, fn) -> Optional[set]:
        """static_argnames set when decorated with jit, else None."""
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = module.dotted(target)
            if name in JitInLoop.JIT_NAMES:
                return self._statics_from_call(
                    dec if isinstance(dec, ast.Call) else None)
            if (isinstance(dec, ast.Call)
                    and name in ("partial", "functools.partial")
                    and dec.args
                    and module.dotted(dec.args[0]) in JitInLoop.JIT_NAMES):
                return self._statics_from_call(dec)
        return None

    def _statics_from_call(self, call: Optional[ast.Call]) -> set:
        statics: set = set()
        if call is None:
            return statics
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        statics.add(n.value)
        return statics

    def _traced_name_in_test(self, module, test, traced) -> Optional[str]:
        for n in ast.walk(test):
            if not (isinstance(n, ast.Name) and n.id in traced):
                continue
            parent = module.parents.get(n)
            if (isinstance(parent, ast.Attribute)
                    and parent.attr in self.STATIC_ATTRS):
                continue
            if (isinstance(parent, ast.Call)
                    and module.dotted(parent.func) in self.STATIC_CALLS):
                continue
            if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
            ):
                continue
            return n.id
        return None


# ---------------------------------------------------------------------------
# rng-key-reuse
# ---------------------------------------------------------------------------

class RngKeyReuse:
    """The same PRNG key consumed by multiple ``jax.random.*`` calls.

    JAX keys are consume-once: feeding one key to two sampling calls
    (or to a sample after a ``split``) yields correlated randomness.
    Every consumption must be followed by ``split`` before the next.
    """

    id = "rng-key-reuse"
    doc = ("a PRNG key fed to multiple jax.random.* calls without an "
           "intervening split/reassignment")

    NON_CONSUMING = {"key", "PRNGKey", "key_data", "wrap_key_data",
                     "key_impl", "default_prng_impl"}

    def check(self, module: Module) -> Iterator[Finding]:
        scopes = [module.tree] + [
            n for n in ast.walk(module.tree) if isinstance(n, _SCOPES)
        ]
        for scope in scopes:
            yield from self._check_scope(module, scope)

    # -- per-scope linear analysis ---------------------------------------

    def _check_scope(self, module, scope) -> Iterator[Finding]:
        events: list[tuple] = []   # ("use"|"def", name, node)
        if isinstance(scope, ast.Lambda):
            self._uses(module, scope, scope.body, events)
        else:
            self._scan_stmts(module, scope, scope.body, events)

        consumed: dict[str, ast.AST] = {}
        defs_in_scope = [
            (name, node) for kind, name, node in events if kind == "def"
        ]
        findings = []
        for kind, name, node in events:
            if kind == "def":
                consumed.pop(name, None)
                continue
            first = consumed.get(name)
            if first is not None:
                findings.append(module.finding(
                    node, self.id,
                    f"PRNG key '{name}' was already consumed at line "
                    f"{first.lineno}; split it and use a fresh subkey",
                ))
            else:
                consumed[name] = node
                loop = self._innermost_loop(module, node, scope)
                if loop is not None and not self._defined_in(
                        defs_in_scope, name, loop):
                    findings.append(module.finding(
                        node, self.id,
                        f"PRNG key '{name}' is consumed every loop "
                        f"iteration but never re-split inside the loop",
                    ))
        yield from findings

    def _innermost_loop(self, module, node, scope):
        for anc in module.ancestors(node):
            if anc is scope or isinstance(anc, _SCOPES):
                return None
            if isinstance(anc, _LOOPS):
                return anc
        return None

    @staticmethod
    def _defined_in(defs, name, loop) -> bool:
        inside = {id(n) for n in ast.walk(loop)}
        return any(n == name and id(dnode) in inside for n, dnode in defs)

    def _scan_stmts(self, module, scope, stmts, events) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are analyzed separately
            if isinstance(stmt, ast.Assign):
                self._uses(module, scope, stmt.value, events)
                for t in stmt.targets:
                    self._defs(t, events)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    self._uses(module, scope, stmt.value, events)
                self._defs(stmt.target, events)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._uses(module, scope, stmt.iter, events)
                self._defs(stmt.target, events)
                self._scan_stmts(module, scope, stmt.body + stmt.orelse,
                                 events)
            elif isinstance(stmt, ast.While):
                self._uses(module, scope, stmt.test, events)
                self._scan_stmts(module, scope, stmt.body + stmt.orelse,
                                 events)
            elif isinstance(stmt, ast.If):
                self._uses(module, scope, stmt.test, events)
                self._scan_stmts(module, scope, stmt.body + stmt.orelse,
                                 events)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._uses(module, scope, item.context_expr, events)
                    if item.optional_vars is not None:
                        self._defs(item.optional_vars, events)
                self._scan_stmts(module, scope, stmt.body, events)
            elif isinstance(stmt, ast.Try):
                self._scan_stmts(module, scope, stmt.body, events)
                for h in stmt.handlers:
                    self._scan_stmts(module, scope, h.body, events)
                self._scan_stmts(module, scope, stmt.orelse + stmt.finalbody,
                                 events)
            else:
                for v in ast.iter_child_nodes(stmt):
                    if isinstance(v, ast.expr):
                        self._uses(module, scope, v, events)

    def _uses(self, module, scope, expr, events) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.NamedExpr):
                self._defs(n.target, events)
            if not isinstance(n, ast.Call):
                continue
            name = module.dotted(n.func) or ""
            if not name.startswith("jax.random."):
                continue
            fn = name.rsplit(".", 1)[1]
            if fn in self.NON_CONSUMING or not n.args:
                continue
            key = n.args[0]
            if isinstance(key, ast.Name):
                # a key fed from inside a nested lambda belongs to that
                # lambda's scope, not this one
                if module.enclosing_function(n) is not self._scope_fn(scope):
                    continue
                events.append(("use", key.id, n))

    @staticmethod
    def _scope_fn(scope):
        return scope if isinstance(scope, _SCOPES) else None

    def _defs(self, target, events) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                events.append(("def", n.id, n))


# ---------------------------------------------------------------------------
# unknown-jax-config
# ---------------------------------------------------------------------------

class UnknownJaxConfig:
    """``jax.config.update`` with an option the installed JAX rejects.

    Config options come and go between JAX releases
    (``jax_num_cpu_devices`` once killed this repo's whole test suite
    at conftest import). Option names are validated against the JAX
    actually installed; version-dependent options are fine when guarded
    by a ``hasattr(jax.config, "<option>")`` capability check.
    """

    id = "unknown-jax-config"
    doc = ("jax.config.update(name, ...) with an option name the "
           "installed JAX does not recognize")

    UPDATE_NAMES = {"jax.config.update", "jax.config.config.update"}

    def __init__(self):
        self._valid: Optional[set] = None
        self._probed = False

    def valid_options(self) -> Optional[set]:
        if not self._probed:
            self._probed = True
            try:
                import jax

                holders = getattr(jax.config, "_value_holders", None)
                if holders:
                    self._valid = set(holders)
                else:
                    self._valid = {
                        n for n in dir(jax.config) if n.startswith("jax_")
                    }
            except Exception:
                self._valid = None  # no JAX installed: rule inert
        return self._valid

    def check(self, module: Module) -> Iterator[Finding]:
        valid = self.valid_options()
        if not valid:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if module.dotted(node.func) not in self.UPDATE_NAMES:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if name in valid or self._capability_guarded(module, node, name):
                continue
            hint = ""
            close = difflib.get_close_matches(name, valid, n=1)
            if close:
                hint = f" (did you mean '{close[0]}'?)"
            yield module.finding(
                node, self.id,
                f"config option '{name}' is not recognized by the "
                f"installed JAX{hint}; guard it with "
                f"hasattr(jax.config, '{name}') or drop it",
            )

    def _capability_guarded(self, module, node, name) -> bool:
        """True when an enclosing if-test probes for the option by name
        (hasattr / membership) AND the update sits in the branch where
        the probe succeeded — an update in the else of a hasattr check
        runs exactly where the option is invalid."""
        child = node
        for anc in module.ancestors(node):
            if isinstance(anc, ast.If):
                try:
                    src = ast.unparse(anc.test)
                except Exception:
                    child = anc
                    continue
                if name in src and ("hasattr" in src or " in " in src):
                    in_body = any(
                        child is n or any(child is d for d in ast.walk(n))
                        for n in anc.body
                    )
                    negated = src.lstrip().startswith("not ")
                    if in_body != negated:
                        return True
            child = anc
        return False


# ---------------------------------------------------------------------------
# unknown-suppression
# ---------------------------------------------------------------------------

class UnknownSuppression:
    """``# jaxlint: disable=<id>`` naming a rule that does not exist.

    A typo'd rule id suppresses nothing while *looking* like a waiver —
    the finding it meant to silence still fires (confusing) or, worse,
    the author believes dangerous code is covered when it never was.
    """

    id = "unknown-suppression"
    doc = ("`# jaxlint: disable=<id>` with a rule id that does not "
           "exist — the typo'd waiver silently suppresses nothing")

    def _valid_ids(self) -> set:
        return {r.id for r in ALL_RULES} | {"all", "parse-error"}

    def check(self, module: Module) -> Iterator[Finding]:
        valid = self._valid_ids()
        for lineno, line in enumerate(module.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            for part in m.group(1).split(","):
                rid = part.strip()
                if not rid or rid in valid:
                    continue
                hint = ""
                close = difflib.get_close_matches(rid, valid, n=1)
                if close:
                    hint = f" (did you mean '{close[0]}'?)"
                yield Finding(
                    file=module.path, line=lineno,
                    col=max(0, line.find("#")), rule=self.id,
                    message=f"'{rid}' is not a jaxlint rule id{hint}; "
                            f"this waiver suppresses nothing",
                    text=module.line_text(lineno),
                )


from tools.jaxlint.lockcheck import (  # noqa: E402
    BlockingUnderLock,
    LockGuardedAttr,
)
from tools.jaxlint.loopcheck import (  # noqa: E402
    AsyncLockBlockingAwait,
    BlockingInAsync,
    BlockingInStream,
    CoroutineNotAwaited,
)
from tools.jaxlint.metriccheck import MetricNameDrift  # noqa: E402
from tools.jaxlint.shardcheck import (  # noqa: E402
    HostSyncOnSharded,
    MeshAxisSpec,
    ShardMapArity,
)

ALL_RULES = [
    HostSyncInHotPath(),
    JitInLoop(),
    TracerControlFlow(),
    RngKeyReuse(),
    UnknownJaxConfig(),
    UnknownSuppression(),
    # lockcheck (lock-discipline dataflow; call-graph-aware)
    LockGuardedAttr(),
    BlockingUnderLock(),
    # shardcheck (mesh-spec validation; call-graph-aware)
    MeshAxisSpec(),
    ShardMapArity(),
    HostSyncOnSharded(),
    # metriccheck (registry <-> reference drift; project-wide)
    MetricNameDrift(),
    # loopcheck (event-loop blocking over the project call graph)
    BlockingInAsync(),
    BlockingInStream(),
    AsyncLockBlockingAwait(),
    CoroutineNotAwaited(),
]
