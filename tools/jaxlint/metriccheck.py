"""metriccheck: the ``localai_*`` series names must agree everywhere.

The obs registry (``localai_tpu/obs/metrics.py``) is the single source
of truth for every exported series. Tests assert exposition substrings,
the README documents the series table, runbooks reference gauges by
name — all as bare strings. A rename that misses one of them is a
silent dashboard outage: the scrape succeeds, the panel goes blank.

Two directions, both findings:

  * a ``localai_*`` name referenced in any scanned file (or the
    README.md sitting next to the scanned ``localai_tpu`` tree) that
    does not resolve to a registry series — the reference is dead;
  * a registry series referenced nowhere (not even the README) — the
    series is undocumented and unasserted, i.e. already half-drifted.

Matching understands the exposition grammar: ``_bucket``/``_sum``/
``_count`` suffixes resolve to their histogram, and a trailing ``_`` or
``*`` in docs (``localai_kv_blocks_*``) is a prefix wildcard.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, Optional

from tools.jaxlint.core import Finding, Module, normalize_path

METRIC_RE = re.compile(r"localai_[a-z0-9_]+\*?")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")
METRIC_CTORS = {"Histogram", "Counter", "Gauge"}
# localai_-prefixed strings that are not metric series
NON_METRICS = {"localai_tpu", "localai_trace_id", "localai_tpu_native"}


class MetricNameDrift:
    id = "metric-name-drift"
    doc = ("localai_* series name referenced in code/tests/README that "
           "is missing from the obs/metrics.py registry, or a registry "
           "series referenced nowhere")

    def __init__(self):
        # name -> (file, line, kind)
        self.registry: Optional[dict[str, tuple]] = None
        self.registry_module: Optional[Module] = None
        # (file, line, token, text)
        self.refs: list[tuple] = []
        self._roots: list[Path] = []

    # -- phase 1: per-module collection -----------------------------------

    def collect(self, module: Module) -> None:
        path = module.path
        if "tools/jaxlint" in path:
            return  # the analyzer's own pattern strings aren't references
        if path.endswith("obs/metrics.py"):
            self._collect_registry(module)
            return
        root = Path(path).resolve()
        if root.parent not in self._roots:
            self._roots.append(root.parent)
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for tok in METRIC_RE.findall(node.value):
                if tok in NON_METRICS:
                    continue
                self.refs.append(
                    (path, node.lineno, tok,
                     module.line_text(node.lineno)))

    def _collect_registry(self, module: Module) -> None:
        self.registry = {}
        self.registry_module = module
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in METRIC_CTORS):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            self.registry[node.args[0].value] = (
                module.path, node.lineno, node.func.id)

    # -- phase 2: cross-file judgement ------------------------------------

    def finalize(self) -> Iterator[Finding]:
        if self.registry is None:
            return  # no registry in the scanned set: pass is inert
        readme_refs = self._readme_refs()
        all_refs = self.refs + readme_refs
        referenced: set[str] = set()
        for file, line, tok, text in all_refs:
            hits = self._resolve(tok)
            if hits:
                referenced.update(hits)
            else:
                yield Finding(
                    file=file, line=line, col=0, rule=self.id,
                    message=(
                        f"series {tok!r} is not in the obs/metrics.py "
                        f"registry — the reference is dead (renamed or "
                        f"never registered)"),
                    text=text,
                )
        for name, (file, line, kind) in sorted(self.registry.items()):
            if name in referenced:
                continue
            mod = self.registry_module
            yield Finding(
                file=file, line=line, col=0, rule=self.id,
                message=(
                    f"registry series {name!r} ({kind}) is referenced "
                    f"nowhere in the scanned tree or README — document "
                    f"it (README metrics table) or drop it"),
                text=mod.line_text(line) if mod else "",
            )

    def _resolve(self, tok: str) -> set:
        """Registry names a reference token matches (empty = dead)."""
        if tok.endswith("*") or tok.endswith("_"):
            prefix = tok.rstrip("*")
            return {n for n in self.registry if n.startswith(prefix)}
        if tok in self.registry:
            return {tok}
        for suf in HIST_SUFFIXES:
            if tok.endswith(suf):
                base = tok[: -len(suf)]
                if self.registry.get(base, ("", 0, ""))[2] == "Histogram":
                    return {base}
        return set()

    def _readme_refs(self) -> list[tuple]:
        """README.md next to the registry (or a scan root): every
        localai_* token with its line, so doc drift is a finding at the
        exact README line."""
        candidates = []
        if self.registry_module is not None:
            # <root>/localai_tpu/obs/metrics.py -> <root>/README.md
            p = Path(self.registry_module.path).resolve()
            candidates.append(p.parents[2] / "README.md")
        for root in self._roots:
            for up in (root, *root.parents[:3]):
                candidates.append(up / "README.md")
        out, seen = [], set()
        for cand in candidates:
            if cand in seen:
                continue
            seen.add(cand)
            if not cand.is_file():
                continue
            for i, line in enumerate(cand.read_text().splitlines(), 1):
                for tok in METRIC_RE.findall(line):
                    if tok not in NON_METRICS:
                        out.append(
                            (normalize_path(str(cand)), i, tok,
                             line.strip()))
            break  # the nearest README is the project README
        return out
