"""Offline usage report: render tenant/goodput tables from a history
snapshot directory, no server required.

``python -m tools.usage_report <snapshot-dir>`` loads the
``history.json`` the serving process left behind (see
``localai_tpu.obs.history``: atomic writer, ``LOCALAI_HISTORY_DIR``) and
prints per-tenant delivered tokens / requests, per-model goodput, and
the waste decomposition — each as the latest cumulative counter value
plus the delta across the loaded window, so "who burned the device this
afternoon" is answerable from a dead snapshot.

``--ingest-bench <dir-or-file>...`` folds ``BENCH_*.json`` result lines
(the one-JSON-line contract from ``bench.py``: ``{"metric", "value",
"unit", ...}`` with an optional nested ``"secondary"``) into the same
store as ``bench.<metric>`` gauge series, timestamped at each file's
mtime — the hardware-round trajectory lands in the one place that
already knows how to downsample and persist it. ``--ingest-autoscale``
does the same for the ``autoscale_report.json`` artifact telemetry_smoke
round 20 leaves behind: the fleet's capacity trajectory replays at its
recorded timestamps and the decision counts / cold-start latency land as
``autoscale.*`` series. ``--save`` writes the merged snapshot back
(tmp + ``os.replace``, same as the live writer).

Raw API keys never appear here for the same reason they never appear in
/metrics: the ledger only ever stored hashed ``t-…`` buckets, so the
snapshot is clean by construction.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Optional

from localai_tpu.obs.history import CAPACITY, History


def _series_span(h: History, name: str, res: int) -> Optional[dict]:
    """Latest value + delta over the ring for one counter series."""
    q = h.query(name, res=res)
    if not q or not q["points"]:
        return None
    pts = q["points"]
    first, last = pts[0], pts[-1]
    return {
        "latest": last["value"],
        "delta": last["value"] - first["value"],
        "from_ts": first["ts"],
        "to_ts": last["ts"],
        "points": len(pts),
    }


def _collect(h: History, prefix: str, res: int) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for name in h.series_names():
        if not name.startswith(prefix + "."):
            continue
        span = _series_span(h, name, res)
        if span is not None:
            out[name[len(prefix) + 1:]] = span
    return out


def _table(title: str, header: list[str], rows: list[list[Any]],
           out) -> None:
    out.write(f"\n{title}\n")
    if not rows:
        out.write("  (no data)\n")
        return
    widths = [max(len(str(header[i])),
                  *(len(str(r[i])) for r in rows))
              for i in range(len(header))]
    fmt = "  " + "  ".join(f"{{:<{w}}}" for w in widths) + "\n"
    out.write(fmt.format(*header))
    out.write(fmt.format(*("-" * w for w in widths)))
    for r in rows:
        out.write(fmt.format(*(str(c) for c in r)))


def build_report(h: History, *, res: int = 10) -> dict:
    """The machine-readable report; the text renderer walks this."""
    tenants = _collect(h, "tenant_tokens", res)
    tenant_reqs = _collect(h, "tenant_requests", res)
    report = {
        "resolution_s": res,
        "tenants": {
            t: {"delivered_tokens": span,
                "requests": tenant_reqs.get(t)}
            for t, span in tenants.items()
        },
        "goodput_tokens": _collect(h, "goodput_tokens", res),
        "waste_tokens": _collect(h, "waste_tokens", res),
        "engine": {
            "tokens_generated": _collect(h, "tokens_generated", res),
            "requests_shed": _collect(h, "requests_shed", res),
        },
        "bench": _collect(h, "bench", res),
        "autoscale": _collect(h, "autoscale", res),
        "fleet_target_replicas": _collect(h, "fleet_target_replicas",
                                          res),
        "series_total": len(h.series_names()),
    }
    # tenants only present in the requests series (all-waste tenants
    # never delivered a token but still made requests)
    for t, span in tenant_reqs.items():
        report["tenants"].setdefault(
            t, {"delivered_tokens": None, "requests": span})
    return report


def render_text(report: dict, out=None) -> None:
    out = out or sys.stdout
    res = report["resolution_s"]
    out.write(f"usage report @ {res}s resolution "
              f"({report['series_total']} series in store)\n")

    rows = []
    for tenant in sorted(report["tenants"]):
        cell = report["tenants"][tenant]
        tok, req = cell["delivered_tokens"], cell["requests"]
        rows.append([
            tenant,
            int(tok["latest"]) if tok else 0,
            int(tok["delta"]) if tok else 0,
            int(req["latest"]) if req else 0,
            int(req["delta"]) if req else 0,
        ])
    _table("per-tenant (hashed buckets — raw keys never stored)",
           ["tenant", "tokens", "Δtokens", "requests", "Δrequests"],
           rows, out)

    rows = [[m, int(s["latest"]), int(s["delta"])]
            for m, s in sorted(report["goodput_tokens"].items())]
    _table("goodput by model", ["model", "tokens", "Δtokens"], rows, out)

    rows = [[r, int(s["latest"]), int(s["delta"])]
            for r, s in sorted(report["waste_tokens"].items())]
    _table("waste by reason", ["reason", "tokens", "Δtokens"], rows, out)

    if report["bench"]:
        rows = [[m, s["latest"], s["points"],
                 time.strftime("%Y-%m-%d %H:%M",
                               time.localtime(s["to_ts"]))]
                for m, s in sorted(report["bench"].items())]
        _table("bench trajectory", ["metric", "last", "points", "as of"],
               rows, out)

    if report["autoscale"] or report["fleet_target_replicas"]:
        rows = [[f"target_replicas.{m}", s["latest"], s["points"],
                 time.strftime("%Y-%m-%d %H:%M",
                               time.localtime(s["to_ts"]))]
                for m, s in sorted(
                    report["fleet_target_replicas"].items())]
        rows += [[m, s["latest"], s["points"],
                  time.strftime("%Y-%m-%d %H:%M",
                                time.localtime(s["to_ts"]))]
                 for m, s in sorted(report["autoscale"].items())]
        _table("elastic capacity",
               ["metric", "last", "points", "as of"], rows, out)


def _bench_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "BENCH_*.json"))))
        else:
            files.append(p)
    return files


def ingest_bench(h: History, paths: list[str]) -> int:
    """Fold BENCH_*.json one-line results into ``bench.<metric>`` gauge
    series at each file's mtime. Returns points ingested; unreadable or
    shapeless files are skipped with a stderr note (report tooling never
    hard-fails on one bad round)."""
    ingested = 0
    for path in _bench_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            ts = os.path.getmtime(path)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"usage_report: skipping {path}: {e}\n")
            continue
        stack = [doc]
        while stack:
            line = stack.pop()
            if not isinstance(line, dict):
                continue
            metric, value = line.get("metric"), line.get("value")
            if isinstance(metric, str) and isinstance(value, (int, float)):
                h.record(f"bench.{metric}", float(value), ts=ts)
                ingested += 1
            if isinstance(line.get("secondary"), dict):
                stack.append(line["secondary"])
    return ingested


def _autoscale_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "autoscale_report*.json"))))
        else:
            files.append(p)
    return files


def ingest_autoscale(h: History, paths: list[str]) -> int:
    """Fold ``autoscale_report.json`` artifacts (telemetry_smoke round
    20) into the store: the capacity trajectory replays point-by-point
    at its recorded timestamps (``fleet_target_replicas.<model>``), and
    the run's decision counts / peak / cold-start latency land as
    ``autoscale.*`` gauges at the file's mtime. Returns points ingested;
    bad files are skipped with a stderr note."""
    ingested = 0
    for path in _autoscale_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            ts = os.path.getmtime(path)
        except (OSError, ValueError) as e:
            sys.stderr.write(f"usage_report: skipping {path}: {e}\n")
            continue
        if not isinstance(doc, dict):
            sys.stderr.write(f"usage_report: skipping {path}: not a "
                             f"JSON object\n")
            continue
        series = doc.get("target_series") or {}
        name = series.get("series") or "fleet_target_replicas.unknown"
        for pt in series.get("points") or []:
            if isinstance(pt, dict) and isinstance(
                    pt.get("value"), (int, float)):
                h.record(name, float(pt["value"]),
                         ts=float(pt.get("ts") or ts))
                ingested += 1
        for action, count in (doc.get("decisions") or {}).items():
            if isinstance(count, (int, float)):
                h.record(f"autoscale.decisions_{action}", float(count),
                         ts=ts)
                ingested += 1
        for key in ("peak_healthy", "cold_start_ms"):
            val = doc.get(key)
            if isinstance(val, (int, float)):
                h.record(f"autoscale.{key}", float(val), ts=ts)
                ingested += 1
    return ingested


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("snapshot_dir", nargs="?", default="",
                        help="directory holding history.json (the live "
                             "LOCALAI_HISTORY_DIR)")
    parser.add_argument("--res", type=int, default=10,
                        choices=sorted(CAPACITY),
                        help="ring resolution to report at (seconds)")
    parser.add_argument("--ingest-bench", nargs="+", default=[],
                        metavar="PATH",
                        help="BENCH_*.json files or directories to fold "
                             "into the store as bench.<metric> series")
    parser.add_argument("--ingest-autoscale", nargs="+", default=[],
                        metavar="PATH",
                        help="autoscale_report*.json files or "
                             "directories (telemetry_smoke round 20) to "
                             "fold into the store as capacity series")
    parser.add_argument("--save", action="store_true",
                        help="write the (merged) snapshot back to "
                             "snapshot_dir")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable report instead "
                             "of tables")
    args = parser.parse_args(argv)

    if not args.snapshot_dir and not args.ingest_bench \
            and not args.ingest_autoscale:
        parser.error("need a snapshot dir, --ingest-bench and/or "
                     "--ingest-autoscale")

    h = History()
    if args.snapshot_dir and not h.load(args.snapshot_dir):
        sys.stderr.write(f"usage_report: no readable history.json under "
                         f"{args.snapshot_dir!r} (starting empty)\n")
    if args.ingest_bench:
        n = ingest_bench(h, args.ingest_bench)
        sys.stderr.write(f"usage_report: ingested {n} bench point(s)\n")
    if args.ingest_autoscale:
        n = ingest_autoscale(h, args.ingest_autoscale)
        sys.stderr.write(f"usage_report: ingested {n} autoscale "
                         f"point(s)\n")
    if args.save:
        if not args.snapshot_dir:
            parser.error("--save needs a snapshot_dir to write to")
        h.save(args.snapshot_dir)

    report = build_report(h, res=args.res)
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        render_text(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
